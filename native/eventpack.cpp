// Native host data path: event-lane assignment + columnar ingest ring.
//
// The reference's performance-critical host machinery is the LMAX Disruptor
// ring buffer behind @Async stream junctions (siddhi-core
// stream/StreamJunction.java:280-316) plus per-event object pooling
// (event/stream/StreamEventPool.java).  The TPU-native equivalent is a
// columnar marshalling path: producers append numeric event rows into a
// fixed columnar ring; the drain side hands contiguous column slabs straight
// to the [P, T] lane packer feeding the device (ops/nfa.py pack_blocks).
//
// Exposed C ABI (loaded via ctypes from siddhi_tpu/native_ext.py — the
// library must keep working without this .so; numpy fallbacks exist):
//   assign_rows   — per-partition running row index for lane packing
//   ring_create / ring_destroy / ring_push / ring_drain / ring_size
#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <new>

extern "C" {

// For each event i with partition pids[i], rows[i] = #earlier events of the
// same partition in this batch; counts[p] ends as the per-partition total.
// Returns the max lane length T (>=0).  O(n), branch-free inner loop.
int64_t assign_rows(const int32_t* pids, int64_t n, int32_t n_partitions,
                    int32_t* rows, int32_t* counts) {
    std::memset(counts, 0, sizeof(int32_t) * (size_t)n_partitions);
    int32_t max_c = 0;
    for (int64_t i = 0; i < n; ++i) {
        int32_t p = pids[i];
        int32_t r = counts[p]++;
        rows[i] = r;
        if (r + 1 > max_c) max_c = r + 1;
    }
    return max_c;
}

// ---------------------------------------------------------------- ring

// Multi-producer columnar ring of numeric event rows.  Values are doubles
// (numeric CEP payloads; strings stay host-side, dictionary-encoded before
// entering the device path).  A coarse mutex is deliberate: producers push
// whole micro-batches, so the lock amortises over hundreds of rows and the
// contended path is memcpy-bound, not lock-bound.
struct Ring {
    int64_t capacity;     // rows
    int32_t n_cols;
    double* values;       // [capacity, n_cols] row-major
    int64_t* ts;          // [capacity]
    int32_t* stream;      // [capacity]
    int32_t* partition;   // [capacity]
    int64_t head;         // next write
    int64_t count;        // rows buffered
    int64_t dropped;      // rows rejected on overflow
    std::mutex mu;
};

Ring* ring_create(int64_t capacity, int32_t n_cols) {
    Ring* r = new (std::nothrow) Ring();
    if (!r) return nullptr;
    r->capacity = capacity;
    r->n_cols = n_cols;
    r->values = new (std::nothrow) double[(size_t)capacity * n_cols];
    r->ts = new (std::nothrow) int64_t[(size_t)capacity];
    r->stream = new (std::nothrow) int32_t[(size_t)capacity];
    r->partition = new (std::nothrow) int32_t[(size_t)capacity];
    r->head = r->count = r->dropped = 0;
    if (!r->values || !r->ts || !r->stream || !r->partition) {
        delete[] r->values; delete[] r->ts; delete[] r->stream;
        delete[] r->partition; delete r;
        return nullptr;
    }
    return r;
}

void ring_destroy(Ring* r) {
    if (!r) return;
    delete[] r->values;
    delete[] r->ts;
    delete[] r->stream;
    delete[] r->partition;
    delete r;
}

// Push m rows (values row-major [m, n_cols]).  Returns rows accepted; the
// remainder is counted in `dropped` (backpressure is the caller's policy,
// mirroring @Async(buffer.size) overflow semantics).
int64_t ring_push(Ring* r, const double* values, const int64_t* ts,
                  const int32_t* stream, const int32_t* partition,
                  int64_t m) {
    std::lock_guard<std::mutex> g(r->mu);
    int64_t space = r->capacity - r->count;
    int64_t take = m < space ? m : space;
    for (int64_t i = 0; i < take; ++i) {
        int64_t slot = (r->head + i) % r->capacity;
        std::memcpy(r->values + slot * r->n_cols, values + i * r->n_cols,
                    sizeof(double) * (size_t)r->n_cols);
        r->ts[slot] = ts[i];
        r->stream[slot] = stream[i];
        r->partition[slot] = partition[i];
    }
    r->head = (r->head + take) % r->capacity;
    r->count += take;
    r->dropped += m - take;
    return take;
}

// Drain up to max_rows oldest rows into contiguous output slabs.
int64_t ring_drain(Ring* r, double* out_values, int64_t* out_ts,
                   int32_t* out_stream, int32_t* out_partition,
                   int64_t max_rows) {
    std::lock_guard<std::mutex> g(r->mu);
    int64_t take = r->count < max_rows ? r->count : max_rows;
    int64_t tail = (r->head - r->count + r->capacity * 2) % r->capacity;
    for (int64_t i = 0; i < take; ++i) {
        int64_t slot = (tail + i) % r->capacity;
        std::memcpy(out_values + i * r->n_cols, r->values + slot * r->n_cols,
                    sizeof(double) * (size_t)r->n_cols);
        out_ts[i] = r->ts[slot];
        out_stream[i] = r->stream[slot];
        out_partition[i] = r->partition[slot];
    }
    r->count -= take;
    return take;
}

int64_t ring_size(Ring* r) {
    std::lock_guard<std::mutex> g(r->mu);
    return r->count;
}

int64_t ring_dropped(Ring* r) {
    std::lock_guard<std::mutex> g(r->mu);
    return r->dropped;
}

}  // extern "C"
